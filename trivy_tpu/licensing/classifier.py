"""License full-text classification for `--license-full` scans.

The reference delegates to google/licenseclassifier v2
(pkg/licensing/classifier.go:36-87), a token-ngram matcher over the
SPDX corpus.  Shipping the full corpus is out of scope here; the same
ALGORITHM runs over distinctive excerpts of the licenses that dominate
real artifacts: each license compiles to a set of word trigrams, a
document's trigram set is intersected with it, and confidence is the
contained fraction — tolerant of reflowed text, punctuation and small
edits, unlike exact phrase search.  Explicit `SPDX-License-Identifier:`
tags classify at confidence 1.0.  Findings below the confidence level
are dropped, mirroring classifier.go:57-60.

Custom corpora extend coverage: `add_license_text(name, text)` compiles
any license body into the matcher at runtime.

Two interchangeable trigram engines back `classify`: the reference
set-of-tuples matcher, and a vectorized engine that interns corpus
words to dense ids, packs each trigram into one int64
(21 bits/word), and intersects sorted unique arrays with
`np.isin` — the same crunch-lane idiom the detector uses for
advisory screening.  Both engines produce identical confidences by
construction (a document trigram containing any out-of-corpus word
can never equal a corpus trigram, and the confidence denominator
only counts corpus grams); `TRIVY_TPU_VECTOR_ANALYZERS=0` or an
overflowing vocabulary falls back to the set engine.
"""

from __future__ import annotations

import os
import re

from trivy_tpu.types.artifact import LicenseFile, LicenseFinding

# File type markers (reference fanal/types: LicenseTypeHeader / File)
TYPE_HEADER = "header"
TYPE_FILE = "license-file"

_SPDX_TAG_RE = re.compile(
    r"SPDX-License-Identifier:\s*([A-Za-z0-9+.\-() ]+?)\s*(?:\*/|-->|$)",
    re.MULTILINE,
)

# Phrases are matched against lowercased text with collapsed whitespace
# and stripped punctuation.  Every phrase list starts with the most
# distinctive sentence of the license body.
_FINGERPRINTS: dict[str, list[str]] = {
    "MIT": [
        "permission is hereby granted free of charge to any person "
        "obtaining a copy of this software",
        "the software is provided as is without warranty of any kind",
        "subject to the following conditions",
    ],
    "Apache-2.0": [
        "apache license version 2 0",
        "licensed under the apache license version 2 0",
        "unless required by applicable law or agreed to in writing",
        "www apache org licenses license 2 0",
    ],
    "BSD-3-Clause": [
        "redistribution and use in source and binary forms",
        "neither the name of",
        "this software is provided by the copyright holders and "
        "contributors as is",
    ],
    "BSD-2-Clause": [
        "redistribution and use in source and binary forms",
        "this software is provided by the copyright holders and "
        "contributors as is",
    ],
    "GPL-2.0": [
        "gnu general public license version 2",
        "free software foundation either version 2 of the license",
        "this program is distributed in the hope that it will be useful",
    ],
    "GPL-3.0": [
        "gnu general public license version 3",
        "free software foundation either version 3 of the license",
        "this program is distributed in the hope that it will be useful",
    ],
    "LGPL-2.1": [
        "gnu lesser general public license version 2 1",
        "free software foundation either version 2 1 of the license",
    ],
    "LGPL-3.0": [
        "gnu lesser general public license version 3",
        "free software foundation either version 3 of the license",
    ],
    "AGPL-3.0": [
        "gnu affero general public license",
        "free software foundation either version 3 of the license",
    ],
    "MPL-2.0": [
        "mozilla public license version 2 0",
        "this source code form is subject to the terms of the mozilla "
        "public license v 2 0",
    ],
    "ISC": [
        "permission to use copy modify and or distribute this software "
        "for any purpose with or without fee is hereby granted",
        "the software is provided as is and the author disclaims all "
        "warranties",
    ],
    "Unlicense": [
        "this is free and unencumbered software released into the "
        "public domain",
        "in jurisdictions that recognize copyright laws",
    ],
    "CC0-1.0": [
        "cc0 1 0 universal",
        "the person who associated a work with this deed has dedicated "
        "the work to the public domain",
    ],
    "EPL-2.0": [
        "eclipse public license v 2 0",
        "this program and the accompanying materials are made available "
        "under the terms of the eclipse public license 2 0",
    ],
    "EPL-1.0": [
        "eclipse public license v 1 0",
    ],
    "Zlib": [
        "this software is provided as is without any express or implied "
        "warranty",
        "altered source versions must be plainly marked as such",
        "the origin of this software must not be misrepresented",
    ],
    "BSL-1.0": [
        "boost software license version 1 0",
        "permission is hereby granted free of charge to any person or "
        "organization obtaining a copy of the software",
    ],
    "WTFPL": [
        "do what the fuck you want to public license",
    ],
    "PostgreSQL": [
        "permission to use copy modify and distribute this software and "
        "its documentation for any purpose without fee",
        "in no event shall the university of california be liable",
    ],
    "OpenSSL": [
        "this product includes software developed by the openssl project",
    ],
    "Artistic-2.0": [
        # NB: the "everyone is permitted to copy and distribute verbatim
        # copies" sentence is shared with every GNU license preamble and
        # must not be used as a fingerprint
        "the artistic license 2 0",
        "aggregating or linking the package",
    ],
    "OFL-1.1": [
        "sil open font license version 1 1",
    ],
    "CDDL-1.0": [
        "common development and distribution license cddl version 1 0",
    ],
    "EUPL-1.2": [
        "european union public licence v 1 2",
    ],
    "MS-PL": [
        "microsoft public license ms pl",
    ],
}

_NORM_RE = re.compile(r"[^a-z0-9]+")

_NGRAM = 3


def _ngrams(text: str) -> set[tuple[str, ...]]:
    words = text.split()
    if len(words) < _NGRAM:
        return {tuple(words)} if words else set()
    return {tuple(words[i:i + _NGRAM])
            for i in range(len(words) - _NGRAM + 1)}


# ------------------------------------------------- packed trigram engine
#
# Corpus words intern to dense ids starting at 1 (0 is the shared
# out-of-corpus id); a trigram packs into one int64 as three 21-bit
# fields.  Grams shorter than the trigram width (phrases under three
# words) stay as Python tuples in a side set — they can never collide
# with a packed value.

_PACK_BITS = 21
_PACK_MAX = (1 << _PACK_BITS) - 1
_VOCAB: dict[str, int] = {}
_PACKED: dict[str, tuple] = {}      # name -> (excerpt|None, [fulls])
_pack_disabled = False


def _vector_enabled() -> bool:
    return (not _pack_disabled
            and os.environ.get("TRIVY_TPU_VECTOR_ANALYZERS", "1") != "0")


def _intern(words: list[str], grow: bool):
    """Map words to dense ids; `grow` extends the vocabulary (corpus
    compile) while documents map unknown words to the OOV id 0."""
    global _pack_disabled
    import numpy as np

    if grow:
        ids = np.empty(len(words), dtype=np.int64)
        for i, w in enumerate(words):
            wid = _VOCAB.get(w)
            if wid is None:
                wid = len(_VOCAB) + 1
                if wid > _PACK_MAX:
                    _pack_disabled = True
                    return None
                _VOCAB[w] = wid
            ids[i] = wid
        return ids
    return np.fromiter((_VOCAB.get(w, 0) for w in words),
                       dtype=np.int64, count=len(words))


def _pack(ids):
    import numpy as np

    packed = ((ids[:-2] << (2 * _PACK_BITS))
              | (ids[1:-1] << _PACK_BITS) | ids[2:])
    return np.unique(packed)


def _compile_packed(texts) -> tuple:
    """Union of the texts' gram sets as (sorted unique packed trigram
    array, frozenset of short grams); None while overflowed."""
    import numpy as np

    arrs, short = [], set()
    for t in texts:
        words = t.split()
        if not words:
            continue
        if len(words) < _NGRAM:
            short.add(tuple(words))
        else:
            ids = _intern(words, grow=True)
            if ids is None:
                return None
            arrs.append(_pack(ids))
    arr = (np.unique(np.concatenate(arrs)) if arrs
           else np.empty(0, dtype=np.int64))
    return arr, frozenset(short)


def _packed_sets(name: str):
    """Packed analogue of `_gram_sets` (same variants, same shapes)."""
    compiled = _PACKED.get(name)
    if compiled is None:
        excerpt = _compile_packed(_FINGERPRINTS.get(name, ()))
        fulls = [_compile_packed([t])
                 for t in _EXTRA_VARIANTS.get(name, ())]
        if excerpt is None or any(f is None for f in fulls):
            return None                          # vocabulary overflow
        if not (excerpt[0].size or excerpt[1]):
            excerpt = None
        compiled = (excerpt, fulls)
        _PACKED[name] = compiled
    return compiled


def _packed_conf(compiled, doc_arr, doc_short) -> float:
    """|corpus grams ∩ doc grams| / |corpus grams|, packed form."""
    import numpy as np

    arr, short = compiled
    total = arr.size + len(short)
    if not total:
        return 0.0
    hits = 0
    if arr.size and doc_arr.size:
        hits = int(np.isin(arr, doc_arr, assume_unique=True).sum())
    if short and doc_short:
        hits += len(short & doc_short)
    return hits / total


_GRAM_SETS: dict[str, list[set]] = {}

# extra whole-text variants per license (the embedded SPDX corpus and
# any user-supplied bodies); each variant matches independently so a
# short distinctive excerpt and a full license body never dilute each
# other's confidence denominator
_EXTRA_VARIANTS: dict[str, list[str]] = {}
_corpus_loaded = False


def _load_corpus() -> None:
    global _corpus_loaded
    if _corpus_loaded:
        return
    _corpus_loaded = True
    from trivy_tpu.licensing.corpus import TEXTS

    for name, text in TEXTS.items():
        _EXTRA_VARIANTS.setdefault(name, []).append(
            _normalize_text(text))
        _GRAM_SETS.pop(name, None)
        _PACKED.pop(name, None)


def _gram_sets(name: str):
    """Compiled word-trigram variants: (excerpt union | None,
    [whole-text gram sets]). Confidence is the max over variants, with
    whole-text matches tracked separately (they outrank excerpt hits in
    the family disambiguation below)."""
    grams = _GRAM_SETS.get(name)
    if grams is None:
        excerpt = set()
        for phrase in _FINGERPRINTS.get(name, ()):
            excerpt |= _ngrams(phrase)
        grams = (excerpt or None,
                 [_ngrams(t) for t in _EXTRA_VARIANTS.get(name, ())])
        _GRAM_SETS[name] = grams
    return grams


def add_license_text(name: str, text: str) -> None:
    """Extend the matcher with a license body (user corpus)."""
    _EXTRA_VARIANTS.setdefault(name, []).append(_normalize_text(text))
    _FINGERPRINTS.setdefault(name, [])
    _GRAM_SETS.pop(name, None)
    _PACKED.pop(name, None)


def _score_sets(norm: str) -> list[tuple[str, float, float]]:
    """Reference engine: (name, excerpt conf, whole-text conf) per
    license, via set-of-tuple trigram intersections."""
    doc_grams = _ngrams(norm)
    out = []
    for name in sorted(set(_FINGERPRINTS) | set(_EXTRA_VARIANTS)):
        excerpt, fulls = _gram_sets(name)
        conf_ex = (len(excerpt & doc_grams) / len(excerpt)
                   if excerpt else 0.0)
        conf_full = max((len(g & doc_grams) / len(g)
                         for g in fulls if g), default=0.0)
        out.append((name, conf_ex, conf_full))
    return out


def _score_packed(norm: str) -> list[tuple[str, float, float]] | None:
    """Vectorized engine: identical confidences to `_score_sets`, or
    None when numpy is unavailable / the vocabulary overflowed."""
    global _pack_disabled
    try:
        import numpy as np
    except ImportError:            # pragma: no cover - numpy is baked in
        _pack_disabled = True
        return None

    names = sorted(set(_FINGERPRINTS) | set(_EXTRA_VARIANTS))
    compiled = []
    for name in names:
        c = _packed_sets(name)
        if c is None:
            return None                          # vocabulary overflow
        compiled.append(c)

    words = norm.split()
    doc_short: set[tuple[str, ...]] = set()
    if len(words) < _NGRAM:
        doc_arr = np.empty(0, dtype=np.int64)
        if words:
            doc_short = {tuple(words)}
    else:
        doc_arr = _pack(_intern(words, grow=False))

    out = []
    for name, (excerpt, fulls) in zip(names, compiled):
        conf_ex = (_packed_conf(excerpt, doc_arr, doc_short)
                   if excerpt is not None else 0.0)
        conf_full = max((_packed_conf(f, doc_arr, doc_short)
                         for f in fulls), default=0.0)
        out.append((name, conf_ex, conf_full))
    return out


def _finding(name: str, confidence: float) -> LicenseFinding:
    return LicenseFinding(
        name=name, confidence=confidence,
        link=f"https://spdx.org/licenses/{name}.html",
    )


def _normalize_text(data: bytes | str) -> str:
    if isinstance(data, bytes):
        data = data.decode("utf-8", errors="replace")
    return _NORM_RE.sub(" ", data.lower()).strip()


def classify(file_path: str, content: bytes | str,
             confidence_level: float = 0.75) -> LicenseFile | None:
    """Classify license text in a file; None when nothing matches."""
    raw = content.decode("utf-8", errors="replace") \
        if isinstance(content, bytes) else content

    findings: list[LicenseFinding] = []
    seen: set[str] = set()
    match_type = TYPE_FILE

    for m in _SPDX_TAG_RE.finditer(raw):
        expr = m.group(1).strip()
        for name in re.split(r"\s+(?:AND|OR|WITH)\s+|[()]", expr):
            name = name.strip()
            if name and name not in seen:
                seen.add(name)
                findings.append(_finding(name, 1.0))
        match_type = TYPE_HEADER

    norm = _normalize_text(raw)
    full_conf: dict[str, float] = {}
    if norm:
        _load_corpus()
        scores = (_score_packed(norm) if _vector_enabled() else None)
        if scores is None:
            scores = _score_sets(norm)
        for name, conf_ex, conf_full in scores:
            if name in seen:
                continue
            conf = max(conf_ex, conf_full)
            if conf >= confidence_level:
                seen.add(name)
                full_conf[name] = conf_full
                findings.append(_finding(name, round(conf, 2)))
                match_type = TYPE_FILE

    # the GNU family shares preamble/boilerplate: a near-exact match of
    # one member (full-text variant >= 0.95) outranks partial matches of
    # its siblings
    gnu = {"GPL-2.0", "GPL-3.0", "LGPL-2.1", "LGPL-3.0", "AGPL-3.0"}
    fam = [f for f in findings if f.name in gnu]
    if len(fam) > 1:
        best_full = max(full_conf.get(f.name, 0.0) for f in fam)
        if best_full >= 0.95:
            # a near-exact whole-text match outranks siblings that only
            # hit shared preamble/excerpt phrases
            for f in fam:
                if full_conf.get(f.name, 0.0) < best_full:
                    findings.remove(f)
        else:
            best = max(f.confidence for f in fam)
            if best >= 0.95:
                for f in fam:
                    if f.confidence < best:
                        findings.remove(f)

    # BSD-2 fingerprint is a subset of BSD-3; prefer the more specific hit
    names = {f.name for f in findings}
    if "BSD-3-Clause" in names and "BSD-2-Clause" in names:
        bsd3 = next(f for f in findings if f.name == "BSD-3-Clause")
        bsd2 = next(f for f in findings if f.name == "BSD-2-Clause")
        if bsd3.confidence >= bsd2.confidence:
            findings.remove(bsd2)

    if not findings:
        return None
    findings.sort(key=lambda f: (-f.confidence, f.name))
    return LicenseFile(type=match_type, file_path=file_path, findings=findings)
