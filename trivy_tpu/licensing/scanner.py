"""License category scanning (reference pkg/licensing/scanner.go):
classify package/file licenses into categories with severities."""

from __future__ import annotations

from trivy_tpu.detector.langpkg import PKG_TARGETS
from trivy_tpu.types.enums import ResultClass
from trivy_tpu.types.report import DetectedLicense, Result

# default category mapping (reference pkg/licensing/category.go defaults)
FORBIDDEN = {"AGPL-1.0", "AGPL-3.0", "CC-BY-NC-1.0", "CC-BY-NC-2.0",
             "CC-BY-NC-2.5", "CC-BY-NC-3.0", "CC-BY-NC-4.0", "FDL-1.0",
             "GFDL-1.0", "GFDL-1.1", "GFDL-1.2", "GFDL-1.3"}
RESTRICTED = {"BCL", "CC-BY-ND-1.0", "CC-BY-ND-2.0", "CC-BY-ND-2.5",
              "CC-BY-ND-3.0", "CC-BY-ND-4.0", "CC-BY-SA-1.0", "CC-BY-SA-2.0",
              "CC-BY-SA-2.5", "CC-BY-SA-3.0", "CC-BY-SA-4.0", "GPL-1.0",
              "GPL-2.0", "GPL-2.0-with-autoconf-exception",
              "GPL-2.0-with-bison-exception", "GPL-2.0-with-classpath-exception",
              "GPL-2.0-with-font-exception", "GPL-2.0-with-GCC-exception",
              "GPL-3.0", "GPL-3.0-with-autoconf-exception",
              "GPL-3.0-with-GCC-exception", "LGPL-2.0", "LGPL-2.1", "LGPL-3.0",
              "NPL-1.0", "NPL-1.1", "OSL-1.0", "OSL-1.1", "OSL-2.0",
              "OSL-2.1", "OSL-3.0", "QPL-1.0", "Sleepycat"}
RECIPROCAL = {"APSL-1.0", "APSL-1.1", "APSL-1.2", "APSL-2.0", "CDDL-1.0",
              "CDDL-1.1", "CPL-1.0", "EPL-1.0", "EPL-2.0", "EUPL-1.1",
              "IPL-1.0", "MPL-1.0", "MPL-1.1", "MPL-2.0", "Ruby"}
NOTICE = {"AFL-1.1", "AFL-1.2", "AFL-2.0", "AFL-2.1", "AFL-3.0", "Apache-1.0",
          "Apache-1.1", "Apache-2.0", "Artistic-1.0", "Artistic-2.0",
          "BSD-2-Clause", "BSD-3-Clause", "BSD-4-Clause", "BSL-1.0",
          "CC-BY-1.0", "CC-BY-2.0", "CC-BY-2.5", "CC-BY-3.0", "CC-BY-4.0",
          "ISC", "MIT", "MS-PL", "NCSA", "OpenSSL", "PHP-3.0", "PHP-3.01",
          "PostgreSQL", "Python-2.0", "Unicode-DFS-2015", "Unicode-DFS-2016",
          "W3C", "X11", "Zlib", "ZPL-1.1", "ZPL-2.0", "ZPL-2.1"}
UNENCUMBERED = {"CC0-1.0", "Unlicense", "0BSD"}
PERMISSIVE: set = set()

_CATEGORY_SEVERITY = {
    "forbidden": "CRITICAL",
    "restricted": "HIGH",
    "reciprocal": "MEDIUM",
    "notice": "LOW",
    "permissive": "LOW",
    "unencumbered": "LOW",
    "unknown": "UNKNOWN",
}


def categorize(license_name: str, custom: dict | None = None) -> tuple[str, str]:
    """-> (category, severity).  The name is normalized to its SPDX id
    first (reference pkg/licensing/scanner.go:24-40)."""
    from trivy_tpu.licensing.normalize import normalize

    license_name = normalize(license_name)
    if custom:
        for cat, names in custom.items():
            if license_name in names:
                return cat, _CATEGORY_SEVERITY.get(cat, "UNKNOWN")
    base = license_name.removesuffix("-only").removesuffix("-or-later")
    for cat, names in (
        ("forbidden", FORBIDDEN), ("restricted", RESTRICTED),
        ("reciprocal", RECIPROCAL), ("notice", NOTICE),
        ("unencumbered", UNENCUMBERED), ("permissive", PERMISSIVE),
    ):
        if license_name in names or base in names:
            return cat, _CATEGORY_SEVERITY[cat]
    return "unknown", "UNKNOWN"


def scan_licenses(detail, options) -> list[Result]:
    results = []
    custom = getattr(options, "license_categories", None)

    os_licenses = []
    for pkg in detail.packages:
        for name in pkg.licenses:
            cat, sev = categorize(name, custom)
            os_licenses.append(DetectedLicense(
                severity=sev, category=cat, pkg_name=pkg.name, name=name,
                confidence=1.0,
            ))
    if os_licenses:
        results.append(Result(
            target="OS Packages", result_class=ResultClass.LICENSE,
            licenses=os_licenses,
        ))

    for app in detail.applications:
        app_licenses = []
        for pkg in app.packages:
            for name in pkg.licenses:
                cat, sev = categorize(name, custom)
                app_licenses.append(DetectedLicense(
                    severity=sev, category=cat, pkg_name=pkg.name,
                    file_path=app.file_path, name=name, confidence=1.0,
                ))
        if app_licenses:
            results.append(Result(
                target=app.file_path
                or PKG_TARGETS.get(app.type, app.type),
                result_class=ResultClass.LICENSE,
                licenses=app_licenses,
            ))

    file_licenses = []
    for lic in detail.licenses:
        for f in lic.findings:
            cat, sev = categorize(f.name, custom)
            file_licenses.append(DetectedLicense(
                severity=sev, category=cat, file_path=lic.file_path,
                name=f.name, confidence=f.confidence, link=f.link,
            ))
    if file_licenses:
        results.append(Result(
            target="Loose File License(s)",
            result_class=ResultClass.LICENSE_FILE,
            licenses=file_licenses,
        ))
    return results
