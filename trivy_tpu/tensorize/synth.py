"""Synthetic advisory DBs shaped like real trivy-db, for scale testing
and benchmarking (VERDICT r1 item 2; ref workload shape
/root/reference/pkg/detector/ospkg/detect.go:66).

Real trivy-db characteristics reproduced here:
- millions of advisories, dominated by OS buckets (debian/ubuntu/
  redhat/alpine releases), each advisory a simple fixed-version row;
  language ecosystems are the minority but carry range expressions
- *name skew*: advisory counts per package follow a Zipf-like law —
  a few hot names ("linux", "firefox", "chromium", "mysql", ...) carry
  thousands of advisories each (debian's "linux" alone has several
  thousand), while the long tail has one or two
- version strings repeat heavily across advisories of one package
"""

from __future__ import annotations

import random

from trivy_tpu.db.model import Advisory
from trivy_tpu.db.store import AdvisoryDB

# hot OS package names, roughly by real advisory volume
HOT_NAMES = [
    "linux", "firefox-esr", "chromium", "mysql-5.7", "imagemagick",
    "openjdk-8", "php7.0", "wireshark", "tcpdump", "qemu", "xen",
    "mariadb-10.1", "ruby2.3", "openssl", "ffmpeg", "binutils",
    "thunderbird", "libreoffice", "ghostscript", "graphicsmagick",
]

OS_BUCKETS = [
    ("debian 11", "deb", "+deb11u"),
    ("debian 12", "deb", "+deb12u"),
    ("ubuntu 20.04", "deb", "-0ubuntu0.20.04."),
    ("ubuntu 22.04", "deb", "-0ubuntu0.22.04."),
    ("alpine 3.18", "apk", "-r"),
    ("alpine 3.19", "apk", "-r"),
    ("rocky 9", "rpm", ".el9"),
    ("redhat 8", "rpm", ".el8"),
]

LANG_ECOS = [
    ("npm", "npm"), ("pip", "pep440"), ("maven", "maven"),
    ("go", "generic"), ("rubygems", "rubygems"), ("cargo", "generic"),
    ("composer", "generic"), ("nuget", "generic"),
]


def _skewed_counts(rng: random.Random, total: int,
                   n_hot: int, hot_min: int) -> list[int]:
    """Advisory count per name summing to ~total: a hot head of up to
    n_hot names (the "linux" shape — capped at a third of the budget,
    scaled down if the budget is small but kept above any realistic
    gather window so eviction is still exercised), then a long
    exponential tail with mean ~5, matching real trivy-db where the
    median package has a couple of advisories."""
    counts: list[int] = []
    if n_hot > 0:
        head_budget = total // 3
        hot_eff = max(min(hot_min, head_budget // max(n_hot, 1) // 2), 600)
        while len(counts) < n_hot and sum(counts) + hot_eff <= head_budget:
            counts.append(hot_eff + rng.randint(0, hot_eff))
    remaining = total - sum(counts)
    while remaining > 0:
        c = 1 + min(int(rng.expovariate(1 / 4.0)), 200)
        c = min(c, remaining)
        counts.append(c)
        remaining -= c
    return counts


def synth_trivy_db(
    n_advisories: int = 2_000_000,
    seed: int = 20260729,
    os_fraction: float = 0.75,
    n_hot: int = 40,
    hot_min: int = 2000,
) -> AdvisoryDB:
    """Build a trivy-db-scale synthetic AdvisoryDB.

    n_hot names receive >= hot_min advisories each (guaranteed to blow
    past any reasonable gather window, exercising host-fallback
    eviction the way debian's "linux" does in the real DB)."""
    rng = random.Random(seed)
    db = AdvisoryDB()

    n_os = int(n_advisories * os_fraction)
    n_lang = n_advisories - n_os

    # --- OS advisories --------------------------------------------------
    # names per bucket chosen so the average name has ~6 advisories
    per_bucket = n_os // len(OS_BUCKETS)
    vcache: list[str] = [
        f"{rng.randint(0, 9)}.{rng.randint(0, 20)}.{rng.randint(0, 30)}"
        for _ in range(4096)
    ]
    for b_i, (bucket, _scheme, suffix) in enumerate(OS_BUCKETS):
        counts = _skewed_counts(
            rng, per_bucket,
            n_hot if b_i == 0 else n_hot // 4,
            hot_min)
        made = 0
        for name_i, cnt in enumerate(counts):
            if made >= per_bucket:
                break
            if cnt > 500:
                name = HOT_NAMES[name_i % len(HOT_NAMES)] + (
                    "" if name_i < len(HOT_NAMES) else f"-{name_i}")
            else:
                name = f"pkg-{bucket.split()[0]}-{name_i}"
            for j in range(cnt):
                if made >= per_bucket:
                    break
                base = vcache[rng.randrange(len(vcache))]
                fixed = "" if rng.random() < 0.08 else \
                    f"{base}{suffix}{rng.randint(1, 9)}"
                db.put_advisory(bucket, name, Advisory(
                    vulnerability_id=f"CVE-{2015 + j % 11}-{b_i}{name_i}{j}",
                    fixed_version=fixed))
                made += 1

    # --- language advisories -------------------------------------------
    per_eco = n_lang // len(LANG_ECOS)
    for e_i, (eco, _scheme) in enumerate(LANG_ECOS):
        counts = _skewed_counts(rng, per_eco, n_hot // 8, hot_min // 2)
        made = 0
        for name_i, cnt in enumerate(counts):
            if made >= per_eco:
                break
            name = f"{eco}-lib-{name_i}"
            for j in range(cnt):
                if made >= per_eco:
                    break
                lo = vcache[rng.randrange(len(vcache))]
                hi = f"{rng.randint(5, 30)}.{rng.randint(0, 20)}.0"
                style = rng.random()
                if style < 0.55:
                    adv = Advisory(
                        vulnerability_id=f"GHSA-{eco}-{name_i}-{j}",
                        vulnerable_versions=[f">={lo}, <{hi}"])
                elif style < 0.85:
                    adv = Advisory(
                        vulnerability_id=f"GHSA-{eco}-{name_i}-{j}",
                        vulnerable_versions=[f"<{hi}"],
                        patched_versions=[f">={hi}"])
                else:
                    adv = Advisory(
                        vulnerability_id=f"GHSA-{eco}-{name_i}-{j}",
                        vulnerable_versions=[f"<{lo} || >={lo}, <{hi}"])
                db.put_advisory(f"{eco}::ghsa", name, adv)
                made += 1
    return db


def synth_queries(db: AdvisoryDB, n_queries: int,
                  seed: int = 7, hot_frac: float = 0.15,
                  miss_frac: float = 0.1) -> list:
    """Draw queries against the synthetic DB: mix of hot names (the
    whole point of the fallback path), tail names, and misses.

    hot_frac=0.15 is the Zipf stress shape (every 7th package is a
    "linux"-class name — far denser than a real scan); hot_frac~0.01 with
    miss_frac~0.35 approximates a real registry crawl where most packages
    have no or few advisories (~1-5 matches/query)."""
    from trivy_tpu.detector.engine import PkgQuery
    from trivy_tpu.tensorize.compile import space_of_bucket

    rng = random.Random(seed)
    pool: list[tuple[str, str, str]] = []  # (space, name, scheme)
    hot_pool: list[tuple[str, str, str]] = []
    for bucket, pkgs in db.buckets.items():
        resolved = space_of_bucket(bucket)
        if resolved is None:
            continue
        space, scheme = resolved
        for name, advs in pkgs.items():
            entry = (space, name, scheme)
            (hot_pool if len(advs) > 500 else pool).append(entry)
    out = []
    for i in range(n_queries):
        r = rng.random()
        if r < hot_frac and hot_pool:
            space, name, scheme = hot_pool[rng.randrange(len(hot_pool))]
        elif r < 1.0 - miss_frac and pool:
            space, name, scheme = pool[rng.randrange(len(pool))]
        else:  # miss
            space, name, scheme = "debian 12", f"nosuch-{i}", "deb"
        v = f"{rng.randint(0, 9)}.{rng.randint(0, 20)}.{rng.randint(0, 30)}"
        if scheme in ("deb", "rpm", "apk"):
            v += f"-{rng.randint(1, 5)}"
        out.append(PkgQuery(space, name, v, scheme))
    return out
