"""Persistent compiled-DB artifact cache (docs/performance.md).

Tensorizing a real advisory DB costs ~11 s of CPU per process start
(BENCH_r05 `db_compile_s`) while the resulting tensor set is ~19 MB —
so an unchanged DB should compile ONCE per digest and every later
process (server restarts, fleet lanes, CLI re-runs) should load the
finished tensors in well under a second.

Layout, riding the PR 2 durability primitives:

    <db_root>/compiled/
      <digest>.<params>.npz             one checksummed tensor set
      <digest>.<params>.npz.quarantine  an entry that failed its
                                        checksum or decode (never
                                        silently reused)

- the npz payload is framed with the `durability.atomic` sha256 footer
  and written via `atomic_write` (tmp + fsync + rename), so a reader
  never sees a torn entry and silent bit rot is caught at load;
- entries are keyed by advisory-DB digest (the OCI generation name
  when the root is generation-managed, else a content hash) plus the
  compile parameters and a format version — any mismatch is a miss;
- a corrupt entry is quarantined aside (like a rejected DB generation)
  and the caller recompiles from the DB: scan results can never differ
  because of cache state, only warm-start latency can.

Hits/misses are counted on the obs spine
(`trivy_tpu_compile_cache_{hits,misses}_total`).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import time

import numpy as np

from trivy_tpu.durability import atomic
from trivy_tpu.log import logger

_log = logger("tensorize.cache")

CACHE_DIR = "compiled"
QUARANTINE_SUFFIX = ".quarantine"
# bump on any change to the serialized layout or to compile_db's row
# semantics that old tensors would misrepresent
FORMAT_VERSION = 1

# bulk CompiledDB array fields serialized verbatim (optional ones may be
# None and are simply absent from the npz)
_ARRAY_FIELDS = (
    "row_h1", "row_h2", "row_lo", "row_hi", "row_flags", "row_adv",
    "hot_h1", "hot_h2", "hot_lo", "hot_hi", "hot_flags", "hot_adv",
    "tall_h1", "tall_h2", "tall_lo", "tall_hi", "tall_flags", "tall_adv",
)


def enabled() -> bool:
    """TRIVY_TPU_COMPILE_CACHE=0 disables the cache entirely."""
    return os.environ.get("TRIVY_TPU_COMPILE_CACHE", "1") != "0"


def params_key(window: int | None) -> str:
    """Compile-parameter component of the entry key. `window` is the
    REQUESTED window (None = auto-sized), not the resolved one — an
    auto entry must not satisfy an explicit-window request."""
    w = "auto" if window is None else str(int(window))
    return f"w{w}-f{FORMAT_VERSION}"


def cache_root(db_root: str) -> str:
    return os.path.join(db_root, CACHE_DIR)


def entry_path(db_root: str, digest: str, window: int | None) -> str:
    return os.path.join(cache_root(db_root),
                        f"{digest}.{params_key(window)}.npz")


def shard_entry_path(db_root: str, digest: str, window: int | None,
                     n_db: int) -> str:
    """Mesh-topology-aware key for a per-shard slice set: the base
    params plus the db-shard count (the slices depend on nothing else —
    dp only replicates them).  Single-chip (and 1x1 mesh) engines never
    create these, so the base entry keys above stay byte-identical to
    the pre-mesh layout."""
    return os.path.join(
        cache_root(db_root),
        f"{digest}.{params_key(window)}.mesh{int(n_db)}.npz")


def db_digest(db_path: str) -> str | None:
    """Digest identifying the advisory-DB bytes an entry was compiled
    from. A generation-managed root reuses the generation's OCI digest
    (its directory name — already verified at install); a flat layout
    hashes the DB payload + metadata files. None when there is no DB."""
    from trivy_tpu.db import generations

    real = os.path.realpath(generations.resolve(db_path))
    base = os.path.basename(real)
    if base.startswith("sha256-"):
        return base
    h = hashlib.sha256()
    found = False
    for name in ("trivy_tpu.db.json.gz", "trivy_tpu.db.json",
                 "trivy.db", "metadata.json"):
        p = os.path.join(real, name)
        try:
            with open(p, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
        except OSError:
            continue
        h.update(b"\x00" + name.encode() + b"\x00")
        if name != "metadata.json":
            found = True
    return "content-" + h.hexdigest() if found else None


def _quarantine(path: str) -> str | None:
    """Move a bad entry aside (numbered, like db.generations) so the
    next lookup recompiles instead of re-reading known-bad bytes."""
    dest = path + QUARANTINE_SUFFIX
    n = 0
    while os.path.exists(dest):
        n += 1
        dest = f"{path}{QUARANTINE_SUFFIX}.{n}"
    try:
        # lint: allow[atomic-write] quarantine move of an already-corrupt entry; rename is atomic
        os.replace(path, dest)
    except OSError:
        return None
    atomic.fsync_dir(os.path.dirname(path))
    _log.warn("quarantined corrupt compiled-DB cache entry", path=dest)
    return dest


def _prune_superseded(root: str, keep_digest: str,
                      min_age_s: float = atomic.STALE_TMP_AGE_S) -> int:
    """Remove entries (and their quarantine copies) for OTHER digests,
    age-gated so a sibling process actively serving the previous
    generation isn't raced mid-rollout. Mirrors db/generations'
    staging sweep: without this, every DB update would leave its
    ~45 MB tensor set behind forever. Returns how many were removed."""
    import time as _time

    removed = 0
    cutoff = _time.time() - min_age_s
    try:
        names = os.listdir(root)
    except OSError:
        return 0
    for name in names:
        if name.startswith(keep_digest + ".") or ".tmp-" in name:
            continue
        if ".keys" in name:
            # advisory-key fingerprint entries (save_keymap) are the
            # OLD side of the monitor's promote-time delta diff — the
            # previous generation's keymap must survive the promote.
            # They age out on their own (KEYMAP_KEEP_S in save_keymap).
            continue
        p = os.path.join(root, name)
        try:
            if os.stat(p).st_mtime > cutoff:
                continue
            os.unlink(p)
            removed += 1
        except OSError:
            continue
    if removed:
        _log.info("pruned superseded compiled-DB cache entries",
                  removed=removed)
    return removed


def save_compiled(db_path: str, cdb, window: int | None,
                  digest: str | None = None,
                  db_meta: dict | None = None) -> str | None:
    """Serialize a CompiledDB under its DB digest + compile params.
    Returns the entry path, or None when saving is impossible/disabled.
    Never raises: the cache is an accelerator, not a dependency."""
    if not enabled():
        return None
    try:
        digest = digest or db_digest(db_path)
        if digest is None:
            return None
        root = cache_root(db_path)
        os.makedirs(root, exist_ok=True)
        atomic.sweep_stale_tmp(root)
        _prune_superseded(root, digest)
        t0 = time.perf_counter()
        arrays = {}
        for f in _ARRAY_FIELDS:
            a = getattr(cdb, f)
            if a is not None:
                arrays[f] = a
        schemes = sorted(cdb.boundaries)
        for i, s in enumerate(schemes):
            arrays[f"bnd_{i}"] = cdb.boundaries[s]
        meta = {
            "format": FORMAT_VERSION,
            "digest": digest,
            "params": params_key(window),
            # identity of the DB object the tensors were compiled FROM
            # (not just the path): guards the load-then-promote race
            # where the on-disk digest has moved to a different
            # generation than the advisories in memory
            "db_meta": db_meta or {},
            "n_advisories": len(cdb.advisories),
            "window": cdb.window,
            "hot_window": cdb.hot_window,
            "tall_window": cdb.tall_window,
            "schemes": schemes,
            "tall_names": sorted(list(k) for k in cdb.tall_names),
            "host_fallback": sorted(
                [s, n, v] for (s, n), v in cdb.host_fallback.items()),
            "stats": cdb.stats,
        }
        arrays["meta_json"] = np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8).copy()
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        path = entry_path(db_path, digest, window)
        atomic.atomic_write(path, atomic.frame(buf.getvalue()),
                            fault_site="compile_cache.save")
        _log.info("compiled-DB cache entry saved", path=path,
                  mb=round(buf.tell() / 1e6, 1),
                  save_s=round(time.perf_counter() - t0, 2))
        return path
    except Exception as exc:  # pragma: no cover - best-effort
        _log.warn("compiled-DB cache save failed", err=str(exc))
        return None


def load_compiled(db_path: str, db, window: int | None,
                  digest: str | None = None,
                  db_meta: dict | None = None):
    """-> CompiledDB from the cache, or None on a miss.

    `db` is the (already loaded) AdvisoryDB the tensors index into:
    the flat advisory list is rebuilt from it in the canonical order
    (`compile.flat_advisories`) — the digest key guarantees the DB
    bytes match what the entry was compiled from, and `db_meta` (the
    loaded DB's metadata document) cross-checks that the in-memory DB
    is the one the entry was compiled FROM even if the on-disk digest
    moved between the DB load and this lookup (concurrent generation
    promote). A metadata mismatch is a plain miss; only corruption
    quarantines.

    A corrupt or inconsistent entry is quarantined and reported as a
    miss so the caller recompiles — zero-diff by construction."""
    from trivy_tpu.obs import metrics as obs_metrics
    from trivy_tpu.tensorize.compile import CompiledDB, flat_advisories

    if not enabled():
        return None
    digest = digest or db_digest(db_path)
    path = entry_path(db_path, digest, window) if digest else None
    if path is None or not os.path.exists(path):
        obs_metrics.COMPILE_CACHE_MISSES.inc()
        return None
    t0 = time.perf_counter()
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as exc:
        # transient read failure (EMFILE, NFS blip): a miss, NOT a
        # quarantine — the entry on disk may be perfectly healthy
        obs_metrics.COMPILE_CACHE_MISSES.inc()
        _log.warn("compiled-DB cache entry unreadable (io); recompiling",
                  path=path, err=str(exc))
        return None
    try:
        body = atomic.unframe(raw)
        if body is raw:
            # a framed entry is the only thing save_compiled writes: a
            # missing footer means the tail was torn off exactly at the
            # marker boundary or the file predates framing — reject
            raise atomic.CorruptEntry("missing checksum footer")
        z = np.load(io.BytesIO(body), allow_pickle=False)
        meta = json.loads(z["meta_json"].tobytes())
        if meta.get("format") != FORMAT_VERSION \
                or meta.get("digest") != digest \
                or meta.get("params") != params_key(window):
            raise atomic.CorruptEntry("metadata/key mismatch")
        if db_meta is not None and meta.get("db_meta") != db_meta:
            # the loaded DB is not the one this entry was compiled
            # from (digest moved under us): a healthy entry for a
            # DIFFERENT generation — miss, don't quarantine
            obs_metrics.COMPILE_CACHE_MISSES.inc()
            _log.warn("compiled-DB cache entry is for a different DB "
                      "generation; recompiling", path=path)
            return None
        advisories = flat_advisories(db)
        if len(advisories) != meta["n_advisories"]:
            raise atomic.CorruptEntry(
                f"advisory count mismatch (entry {meta['n_advisories']}, "
                f"db {len(advisories)})")
        arr = {f: (z[f] if f in z.files else None)
               for f in _ARRAY_FIELDS}
        for f in _ARRAY_FIELDS[:6]:  # main row tensors are mandatory
            if arr[f] is None:
                raise atomic.CorruptEntry(f"missing array {f}")
        boundaries = {s: z[f"bnd_{i}"]
                      for i, s in enumerate(meta["schemes"])}
        cdb = CompiledDB(
            **arr,
            boundaries=boundaries,
            advisories=advisories,
            host_fallback={(s, n): v
                           for s, n, v in meta["host_fallback"]},
            window=meta["window"],
            hot_window=meta["hot_window"],
            tall_window=meta["tall_window"],
            tall_names={tuple(t) for t in meta["tall_names"]},
            stats=dict(meta["stats"], compile_cache="hit"),
        )
    except Exception as exc:
        _quarantine(path)
        obs_metrics.COMPILE_CACHE_MISSES.inc()
        _log.warn("compiled-DB cache entry unreadable; recompiling",
                  path=path, err=str(exc))
        return None
    obs_metrics.COMPILE_CACHE_HITS.inc()
    _log.info("compiled-DB cache hit", path=path,
              load_s=round(time.perf_counter() - t0, 3),
              rows=cdb.n_rows)
    return cdb


# ---------------------------------------------- compiled secret-NFA programs

# bump on any change to the serialized tier layout; the ruleset digest
# already folds in the kernel/anchor constants (secret/scanner.py
# _ruleset_digest), so semantic screen changes key new entries on their
# own
NFA_FORMAT_VERSION = 1


def nfa_entry_path(cache_dir: str, digest: str) -> str:
    return os.path.join(cache_dir, CACHE_DIR,
                        f"nfa-{digest}.f{NFA_FORMAT_VERSION}.npz")


def save_nfa(cache_dir: str, digest: str, arrays: dict,
             meta: dict) -> str | None:
    """Persist a compiled secret-NFA program (anchor class rows + tier
    metadata, serialized by SecretScanner) under its ruleset digest.
    Same framing / atomic-write / never-raise contract as the
    compiled-DB tensor entries: the cache is an accelerator, not a
    dependency."""
    if not enabled():
        return None
    try:
        root = os.path.join(cache_dir, CACHE_DIR)
        os.makedirs(root, exist_ok=True)
        atomic.sweep_stale_tmp(root)
        t0 = time.perf_counter()
        doc = dict(meta, format=NFA_FORMAT_VERSION, digest=digest)
        payload = dict(arrays)
        payload["meta_json"] = np.frombuffer(
            json.dumps(doc).encode(), dtype=np.uint8).copy()
        buf = io.BytesIO()
        np.savez(buf, **payload)
        path = nfa_entry_path(cache_dir, digest)
        atomic.atomic_write(path, atomic.frame(buf.getvalue()),
                            fault_site="compile_cache.save")
        _log.debug("compiled secret-NFA cache entry saved", path=path,
                   kb=round(buf.tell() / 1e3, 1),
                   save_s=round(time.perf_counter() - t0, 3))
        return path
    except Exception as exc:  # pragma: no cover - best-effort
        _log.warn("compiled secret-NFA cache save failed", err=str(exc))
        return None


def load_nfa(cache_dir: str, digest: str):
    """-> (arrays dict, meta dict) for a cached compiled-NFA program,
    or None on a miss.  Corrupt / mismatched entries are quarantined
    (PR 2 corrupt→evict→miss self-healing) and the scanner recompiles
    from the ruleset — scan results can never differ because of cache
    state, only warm-start latency can."""
    from trivy_tpu.obs import metrics as obs_metrics

    if not enabled():
        return None
    path = nfa_entry_path(cache_dir, digest)
    if not os.path.exists(path):
        obs_metrics.SECRET_NFA_CACHE_MISSES.inc()
        return None
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as exc:
        # transient read failure: a miss, NOT a quarantine
        obs_metrics.SECRET_NFA_CACHE_MISSES.inc()
        _log.warn("compiled secret-NFA cache entry unreadable (io); "
                  "recompiling", path=path, err=str(exc))
        return None
    try:
        body = atomic.unframe(raw)
        if body is raw:
            raise atomic.CorruptEntry("missing checksum footer")
        z = np.load(io.BytesIO(body), allow_pickle=False)
        meta = json.loads(z["meta_json"].tobytes())
        if meta.get("format") != NFA_FORMAT_VERSION \
                or meta.get("digest") != digest:
            raise atomic.CorruptEntry("metadata/key mismatch")
        arrays = {k: z[k] for k in z.files if k != "meta_json"}
    except Exception as exc:
        _quarantine(path)
        obs_metrics.SECRET_NFA_CACHE_MISSES.inc()
        _log.warn("compiled secret-NFA cache entry unreadable; "
                  "recompiling", path=path, err=str(exc))
        return None
    obs_metrics.SECRET_NFA_CACHE_HITS.inc()
    _log.debug("compiled secret-NFA cache hit", path=path)
    return arrays, meta


# ------------------------------------------------- advisory-key fingerprints

# bump on any change to the fingerprint computation: old/new entries
# with different formats never diff against each other (the monitor
# falls back to a full rescan instead)
KEYMAP_VERSION = 1
# fingerprint entries for superseded digests are kept this long (they
# are the OLD side of promote-time delta diffs, so the _prune_superseded
# sweep exempts them), then aged out by save_keymap
KEYMAP_KEEP_S = 7 * 24 * 3600.0


def keymap_path(db_root: str, digest: str) -> str:
    return os.path.join(cache_root(db_root),
                        f"{digest}.keys{KEYMAP_VERSION}.json.gz")


def advisory_fingerprints(db) -> dict[tuple[str, str], str]:
    """Per-(space, name) content digest of a loaded AdvisoryDB.

    The key space matches the match engine's query space exactly
    (`tensorize.compile.space_of_bucket`): all language buckets of one
    ecosystem collapse onto the "eco::" prefix space, OS buckets are
    their own space, and buckets with no resolvable scheme are skipped —
    they are invisible to matching, so their churn cannot change any
    finding.  Two DB generations agreeing on a key's digest therefore
    match identically for every query on that key, which is the load-
    bearing invariant of the monitor's delta re-scoring
    (docs/monitoring.md)."""
    from trivy_tpu.tensorize.compile import space_of_bucket

    acc: dict[tuple[str, str], list[str]] = {}
    space_by_bucket: dict[str, str | None] = {}
    for bucket, pkgs in db.buckets.items():
        space = space_by_bucket.get(bucket, "?")
        if space == "?":
            resolved = space_of_bucket(bucket)
            space = resolved[0] if resolved else None
            space_by_bucket[bucket] = space
        if space is None:
            continue
        for name, advs in pkgs.items():
            entries = acc.setdefault((space, name), [])
            for a in advs:
                entries.append(bucket + "\x1f" + json.dumps(
                    a.to_json(), sort_keys=True, separators=(",", ":")))
    out: dict[tuple[str, str], str] = {}
    for key, entries in acc.items():
        h = hashlib.sha256()
        for e in sorted(entries):
            h.update(e.encode())
            h.update(b"\x00")
        out[key] = h.hexdigest()[:32]
    return out


def save_keymap(db_path: str, db, digest: str | None = None) -> str | None:
    """Persist the advisory-key fingerprint table for `digest` next to
    the compiled entries (skipped when it already exists — fingerprints
    are content-addressed by the digest).  Same framing / atomic-write /
    never-raise contract as the tensor entries.

    Guarded against the load-then-promote race the tensor entries guard
    with their db_meta cross-check: if the on-disk root no longer
    resolves to `digest`, or its metadata document disagrees with the
    in-memory DB's, the save is SKIPPED — writing another generation's
    fingerprints under this digest would poison every later delta diff
    that trusts the content-addressed entry."""
    import gzip

    if not enabled():
        return None
    try:
        digest = digest or db_digest(db_path)
        if digest is None:
            return None
        if db_digest(db_path) != digest:
            _log.warn("advisory-key fingerprint save skipped: DB root "
                      "moved to another generation", digest=digest)
            return None
        from trivy_tpu.db import generations

        meta_path = os.path.join(
            os.path.realpath(generations.resolve(db_path)),
            "metadata.json")
        try:
            with open(meta_path, encoding="utf-8") as f:
                on_disk_meta = json.load(f)
        except (OSError, ValueError):
            on_disk_meta = None
        if on_disk_meta is not None \
                and on_disk_meta != db.meta.to_json():
            _log.warn("advisory-key fingerprint save skipped: loaded "
                      "DB's metadata disagrees with the on-disk root",
                      digest=digest)
            return None
        path = keymap_path(db_path, digest)
        if os.path.exists(path):
            return path
        root = cache_root(db_path)
        os.makedirs(root, exist_ok=True)
        # age out fingerprint entries for long-gone generations (they
        # survive _prune_superseded by design; see KEYMAP_KEEP_S)
        keep_cutoff = time.time() - KEYMAP_KEEP_S
        for name in os.listdir(root):
            if ".keys" not in name or name.startswith(digest + "."):
                continue
            p = os.path.join(root, name)
            try:
                if os.stat(p).st_mtime < keep_cutoff:
                    os.unlink(p)
            except OSError:
                continue
        t0 = time.perf_counter()
        keys = advisory_fingerprints(db)
        doc = {
            "format": KEYMAP_VERSION,
            "digest": digest,
            "schema": db.meta.version,
            "keys": [[s, n, d] for (s, n), d in sorted(keys.items())],
        }
        payload = gzip.compress(
            json.dumps(doc, separators=(",", ":")).encode(), mtime=0)
        atomic.atomic_write(path, atomic.frame(payload),
                            fault_site="compile_cache.save")
        _log.info("advisory-key fingerprint entry saved", path=path,
                  keys=len(keys),
                  save_s=round(time.perf_counter() - t0, 2))
        return path
    except Exception as exc:  # pragma: no cover - best-effort
        _log.warn("advisory-key fingerprint save failed", err=str(exc))
        return None


def load_keymap(db_path: str, digest: str | None):
    """-> {"schema": int, "keys": {(space, name): digest}} for a cached
    fingerprint entry, or None on a miss.  Corrupt entries quarantine
    (the monitor then recomputes or falls back to a full rescan — never
    a wrong delta)."""
    import gzip

    if not enabled() or not digest:
        return None
    path = keymap_path(db_path, digest)
    if not os.path.exists(path):
        return None
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as exc:
        _log.warn("advisory-key fingerprint entry unreadable (io)",
                  path=path, err=str(exc))
        return None
    try:
        body = atomic.unframe(raw)
        if body is raw:
            raise atomic.CorruptEntry("missing checksum footer")
        doc = json.loads(gzip.decompress(body))
        if doc.get("format") != KEYMAP_VERSION \
                or doc.get("digest") != digest:
            raise atomic.CorruptEntry("metadata/key mismatch")
        keys = {(s, n): d for s, n, d in doc["keys"]}
    except Exception as exc:
        _quarantine(path)
        _log.warn("advisory-key fingerprint entry corrupt; quarantined",
                  path=path, err=str(exc))
        return None
    return {"schema": doc.get("schema"), "keys": keys}


def save_shards(db_path: str, cdb, n_db: int, shards,
                window: int | None = None, digest: str | None = None,
                db_meta: dict | None = None) -> str | None:
    """Serialize a mesh's per-shard slice set (`shards` =
    (h1s [D,S], tables [D,S,L], shard_len, shard_base) from
    ops/match.host_shards) under the digest + params +
    db-shard-count key.  Same framing/quarantine/never-raise contract
    as save_compiled — the cache is an accelerator, not a dependency.
    """
    if not enabled():
        return None
    try:
        digest = digest or db_digest(db_path)
        if digest is None:
            return None
        h1s, tables, shard_len, shard_base = shards
        root = cache_root(db_path)
        os.makedirs(root, exist_ok=True)
        t0 = time.perf_counter()
        meta = {
            "format": FORMAT_VERSION,
            "digest": digest,
            "params": params_key(window),
            "db_meta": db_meta or {},
            "n_db": int(n_db),
            "n_rows": int(cdb.n_rows),
            # the RESOLVED window (the halo width baked into the
            # slices), distinct from the requested window in `params`
            "window": int(cdb.window),
            "shard_len": int(shard_len),
            "shard_base": int(shard_base),
        }
        arrays = {
            "h1s": h1s,
            "tables": tables,
            "meta_json": np.frombuffer(
                json.dumps(meta).encode(), dtype=np.uint8).copy(),
        }
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        path = shard_entry_path(db_path, digest, window, n_db)
        atomic.atomic_write(path, atomic.frame(buf.getvalue()),
                            fault_site="compile_cache.save")
        _log.info("mesh shard-slice cache entry saved", path=path,
                  n_db=n_db, mb=round(buf.tell() / 1e6, 1),
                  save_s=round(time.perf_counter() - t0, 2))
        return path
    except Exception as exc:  # pragma: no cover - best-effort
        _log.warn("mesh shard-slice cache save failed", err=str(exc))
        return None


def load_shards(db_path: str, cdb, n_db: int,
                window: int | None = None, digest: str | None = None,
                db_meta: dict | None = None):
    """-> (h1s, tables, shard_len, shard_base) from the cache, or None
    on a miss.  `cdb` is the (already loaded/compiled) CompiledDB the
    slices must belong to: row count and resolved window cross-check
    the entry, and a `db_meta` mismatch is a plain miss (generation
    moved), never a quarantine.  Corrupt entries quarantine and the
    caller re-slices — zero scan diff by construction."""
    from trivy_tpu.obs import metrics as obs_metrics

    if not enabled():
        return None
    digest = digest or db_digest(db_path)
    path = shard_entry_path(db_path, digest, window, n_db) \
        if digest else None
    if path is None or not os.path.exists(path):
        obs_metrics.COMPILE_CACHE_MISSES.inc()
        return None
    t0 = time.perf_counter()
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as exc:
        obs_metrics.COMPILE_CACHE_MISSES.inc()
        _log.warn("mesh shard-slice cache entry unreadable (io); "
                  "re-slicing", path=path, err=str(exc))
        return None
    try:
        body = atomic.unframe(raw)
        if body is raw:
            raise atomic.CorruptEntry("missing checksum footer")
        z = np.load(io.BytesIO(body), allow_pickle=False)
        meta = json.loads(z["meta_json"].tobytes())
        if meta.get("format") != FORMAT_VERSION \
                or meta.get("digest") != digest \
                or meta.get("params") != params_key(window) \
                or meta.get("n_db") != int(n_db):
            raise atomic.CorruptEntry("metadata/key mismatch")
        if db_meta is not None and meta.get("db_meta") != db_meta:
            obs_metrics.COMPILE_CACHE_MISSES.inc()
            _log.warn("mesh shard-slice cache entry is for a different "
                      "DB generation; re-slicing", path=path)
            return None
        if meta.get("n_rows") != int(cdb.n_rows) \
                or meta.get("window") != int(cdb.window):
            raise atomic.CorruptEntry(
                f"slice/DB mismatch (entry rows={meta.get('n_rows')} "
                f"window={meta.get('window')}, db rows={cdb.n_rows} "
                f"window={cdb.window})")
        h1s, tables = z["h1s"], z["tables"]
        shard_len = int(meta["shard_len"])
        shard_base = int(meta["shard_base"])
        if h1s.shape != (n_db, shard_len) \
                or tables.shape[:2] != (n_db, shard_len):
            raise atomic.CorruptEntry("shard array shape mismatch")
    except Exception as exc:
        _quarantine(path)
        obs_metrics.COMPILE_CACHE_MISSES.inc()
        _log.warn("mesh shard-slice cache entry unreadable; re-slicing",
                  path=path, err=str(exc))
        return None
    obs_metrics.COMPILE_CACHE_HITS.inc()
    _log.info("mesh shard-slice cache hit", path=path, n_db=n_db,
              load_s=round(time.perf_counter() - t0, 3))
    return h1s, tables, shard_len, shard_base


# -------------------------------------------------- cross-host slice entries


def host_slice_entry_path(db_root: str, digest: str,
                          window: int | None, n_hosts: int,
                          host_index: int, n_db: int) -> str:
    """Key for ONE host's slice of the distributed MeshDB's global
    shard partition (ops/dcn.py): base params plus the host topology
    and the GLOBAL db-shard count.  Per-process by construction — each
    host warm-loads only its own entry, never the full table."""
    return os.path.join(
        cache_root(db_root),
        f"{digest}.{params_key(window)}"
        f".dcn{int(n_hosts)}h{int(host_index)}.mesh{int(n_db)}.npz")


def save_host_slice(db_path: str, *, digest: str, window: int | None,
                    db_meta: dict | None, n_hosts: int, host_index: int,
                    n_db: int, n_rows: int, resolved_window: int,
                    shard_len: int, shard_base: int,
                    h1s, tables) -> str | None:
    """Serialize one host's slice (its contiguous run of global
    shards, `h1s` [db_local, S] / `tables` [db_local, S, L]) under the
    digest + params + host-topology key.  Same framing / quarantine /
    never-raise contract as the other entries.  Written by the
    coordinator when it slices the full table (every host's entry at
    once) and by a worker that received a pushed slice (its own)."""
    if not enabled():
        return None
    try:
        if digest is None:
            return None
        root = cache_root(db_path)
        os.makedirs(root, exist_ok=True)
        t0 = time.perf_counter()
        meta = {
            "format": FORMAT_VERSION,
            "digest": digest,
            "params": params_key(window),
            "db_meta": db_meta or {},
            "n_hosts": int(n_hosts),
            "host_index": int(host_index),
            "n_db": int(n_db),
            "n_rows": int(n_rows),
            "window": int(resolved_window),
            "shard_len": int(shard_len),
            "shard_base": int(shard_base),
        }
        arrays = {
            "h1s": np.asarray(h1s),
            "tables": np.asarray(tables),
            "meta_json": np.frombuffer(
                json.dumps(meta).encode(), dtype=np.uint8).copy(),
        }
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        path = host_slice_entry_path(db_path, digest, window, n_hosts,
                                     host_index, n_db)
        atomic.atomic_write(path, atomic.frame(buf.getvalue()),
                            fault_site="compile_cache.save")
        _log.info("host-slice cache entry saved", path=path,
                  host=host_index, n_hosts=n_hosts,
                  mb=round(buf.tell() / 1e6, 1),
                  save_s=round(time.perf_counter() - t0, 2))
        return path
    except Exception as exc:  # pragma: no cover - best-effort
        _log.warn("host-slice cache save failed", err=str(exc))
        return None


def load_host_slice(db_path: str, *, digest: str | None,
                    window: int | None, db_meta: dict | None,
                    n_hosts: int, host_index: int, n_db: int,
                    n_rows: int | None = None,
                    resolved_window: int | None = None):
    """-> {"h1s", "tables", "shard_len", "shard_base", "n_rows",
    "window"} for one host's cached slice, or None on a miss.  The key
    + row/window cross-checks guarantee the slice is exactly what
    `ops/match.host_shards` over the same DB bytes produces — a
    `db_meta` mismatch (generation moved) is a plain miss; corruption
    quarantines and the host falls back to a coordinator push — zero
    scan diff by construction."""
    from trivy_tpu.obs import metrics as obs_metrics

    if not enabled() or not digest:
        return None
    path = host_slice_entry_path(db_path, digest, window, n_hosts,
                                 host_index, n_db)
    if not os.path.exists(path):
        obs_metrics.COMPILE_CACHE_MISSES.inc()
        return None
    t0 = time.perf_counter()
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as exc:
        obs_metrics.COMPILE_CACHE_MISSES.inc()
        _log.warn("host-slice cache entry unreadable (io)",
                  path=path, err=str(exc))
        return None
    try:
        body = atomic.unframe(raw)
        if body is raw:
            raise atomic.CorruptEntry("missing checksum footer")
        z = np.load(io.BytesIO(body), allow_pickle=False)
        meta = json.loads(z["meta_json"].tobytes())
        if meta.get("format") != FORMAT_VERSION \
                or meta.get("digest") != digest \
                or meta.get("params") != params_key(window) \
                or meta.get("n_hosts") != int(n_hosts) \
                or meta.get("host_index") != int(host_index) \
                or meta.get("n_db") != int(n_db):
            raise atomic.CorruptEntry("metadata/key mismatch")
        if db_meta is not None and meta.get("db_meta") != db_meta:
            obs_metrics.COMPILE_CACHE_MISSES.inc()
            _log.warn("host-slice cache entry is for a different DB "
                      "generation; falling back", path=path)
            return None
        if (n_rows is not None and meta.get("n_rows") != int(n_rows)) \
                or (resolved_window is not None
                    and meta.get("window") != int(resolved_window)):
            raise atomic.CorruptEntry(
                f"slice/DB mismatch (entry rows={meta.get('n_rows')} "
                f"window={meta.get('window')}, want rows={n_rows} "
                f"window={resolved_window})")
        h1s, tables = z["h1s"], z["tables"]
        db_local = int(n_db) // int(n_hosts)
        if h1s.shape != (db_local, int(meta["shard_len"])) \
                or tables.shape[:2] != (db_local, int(meta["shard_len"])):
            raise atomic.CorruptEntry("slice array shape mismatch")
    except Exception as exc:
        _quarantine(path)
        obs_metrics.COMPILE_CACHE_MISSES.inc()
        _log.warn("host-slice cache entry unreadable; falling back",
                  path=path, err=str(exc))
        return None
    obs_metrics.COMPILE_CACHE_HITS.inc()
    _log.info("host-slice cache hit", path=path, host=host_index,
              load_s=round(time.perf_counter() - t0, 3))
    return {
        "h1s": h1s, "tables": tables,
        "shard_len": int(meta["shard_len"]),
        "shard_base": int(meta["shard_base"]),
        "n_rows": int(meta["n_rows"]),
        "window": int(meta["window"]),
    }
