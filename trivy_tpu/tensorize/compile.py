"""DB tensorization: advisory buckets -> dense join + interval tensors.

This is the heart of the TPU design (SURVEY.md §7 step 2). Host-side, once
per DB load:

1. Every advisory is compiled to a union of version intervals over its
   scheme's total order (constraint algebra from trivy_tpu.versioning).
2. All interval boundary versions are encoded to fixed-width byte keys and
   sorted per scheme -> boundary table B_s. Interval bounds become *scaled
   ranks*: a version v ranks s = 2*searchsorted(B, key(v)) + (key(v) in B),
   so `lo_rank <= s <= hi_rank` is an exact containment test using nothing
   but int32 compares — all the device ever does.
3. Rows are sorted by (h1, h2) of the (match-space, package-name) join key;
   the kernel binary-searches h1 and gathers a fixed window.

Anything that cannot be encoded exactly (unparseable/overflow versions,
un-intervalable constraints) gets FLAG_NEEDS_HOST: the kernel emits such
rows as candidates whenever the name matches, and the host rescreen applies
the exact comparators — zero-diff by construction.

Names with more than `window` rows are evicted to a host-side fallback map
(tested: rare; e.g. "linux" in Debian).
"""

from __future__ import annotations

import threading

from trivy_tpu.analysis.witness import make_lock
from dataclasses import dataclass, field

import numpy as np

from trivy_tpu import versioning
from trivy_tpu.db.model import Advisory
from trivy_tpu.db.store import AdvisoryDB
from trivy_tpu.log import logger
from trivy_tpu.utils.hashing import join_key
from trivy_tpu.versioning import Constraints
from trivy_tpu.versioning.base import KEY_BYTES, ParseError

FLAG_NEEDS_HOST = 1
FLAG_RESCREEN = 2  # exact rank, but match semantics exceed pure intervals
FLAG_PRE_ONLY = 4  # row only matches queries flagged pre-release

INT32_MAX = np.int32(2**31 - 1)

_log = logger("tensorize")


def _ranks_of(bounds: np.ndarray, keys: list[bytes]) -> np.ndarray:
    """Vectorized scaled ranks of encoded keys in a sorted S-dtype
    boundary table: rank = 2*pos + (key present). Shares the NUL-strip
    equality caveat with _rank_of (S-dtype compares strip trailing
    NULs on both sides, so direct array equality is exact)."""
    arr = np.array(keys, dtype=bounds.dtype)
    pos = np.searchsorted(bounds, arr, side="left").astype(np.int64)
    in_range = pos < len(bounds)
    eq = np.zeros(len(keys), dtype=bool)
    eq[in_range] = bounds[pos[in_range]] == arr[in_range]
    return (2 * pos + eq).astype(np.int32)


def _rank_of(bounds: np.ndarray | None, key: bytes) -> int:
    """Scaled rank of an encoded key in a sorted S-dtype boundary table.
    NB: numpy S-dtype strips trailing NULs, so equality must compare the
    stripped forms (ordering via searchsorted is unaffected: shorter
    strings compare as NUL-padded)."""
    if bounds is None or len(bounds) == 0:
        return 0
    i = int(np.searchsorted(bounds, np.bytes_(key), side="left"))
    eq = i < len(bounds) and bytes(bounds[i]) == key.rstrip(b"\x00")
    return 2 * i + (1 if eq else 0)


def space_of_bucket(bucket: str) -> tuple[str, str] | None:
    """bucket -> (space key, scheme name), or None if not matchable.

    Language buckets "eco::source" all share the space "eco::" (prefix
    lookup semantics, reference pkg/detector/library/driver.go:115-124).
    OS buckets "<family> <release>" are their own space."""
    if "::" in bucket:
        eco = bucket.split("::", 1)[0]
        name = versioning.ECOSYSTEM_SCHEME.get(eco)
        return (f"{eco}::", name) if name else None
    family = bucket.rsplit(" ", 1)[0] if " " in bucket else bucket
    name = versioning.OS_SCHEME.get(family)
    return (bucket, name) if name else None


@dataclass
class _Row:
    h1: int
    h2: int
    lo_key: bytes | None  # None = unbounded
    lo_incl: bool
    hi_key: bytes | None
    hi_incl: bool
    scheme: str
    flags: int
    adv_idx: int


@dataclass
class PackageBatch:
    """Device-ready encoding of a batch of (space, name, version) queries."""

    h1: np.ndarray  # uint32[B]
    h2: np.ndarray  # uint32[B]
    rank: np.ndarray  # int32[B]
    flags: np.ndarray  # int32[B]
    queries: list  # original (space, name, version, scheme_name)
    # engine-interned (space,name) / (scheme,version) tokens, filled when
    # the CompiledDB carries token dicts (saves the match engine a second
    # per-query Python pass during result collection)
    ntok: np.ndarray | None = None  # int64[B]
    vtok: np.ndarray | None = None  # int64[B]
    # hot/tall tier routing per query (0=main, 1=hot, 2=tall), gathered
    # from the name intern table so dispatch never probes dicts per item
    route: np.ndarray | None = None  # int8[B]


class _Grow:
    """Append-only numpy array with doubling growth: dense-id intern
    tables gather per batch with ONE fancy index instead of a dict
    probe per query."""

    __slots__ = ("a", "n")

    def __init__(self, dtype, cap: int = 256):
        self.a = np.empty(cap, dtype=dtype)
        self.n = 0

    def append(self, v) -> None:
        if self.n == len(self.a):
            grown = np.empty(len(self.a) * 2, dtype=self.a.dtype)
            grown[: self.n] = self.a
            self.a = grown
        self.a[self.n] = v
        self.n += 1

    def view(self) -> np.ndarray:
        return self.a[: self.n]


@dataclass
class CompiledDB:
    # row tensors, sorted by (h1, h2)
    row_h1: np.ndarray  # uint32[N]
    row_h2: np.ndarray  # uint32[N]
    row_lo: np.ndarray  # int32[N] scaled rank
    row_hi: np.ndarray  # int32[N]
    row_flags: np.ndarray  # int32[N]
    row_adv: np.ndarray  # int32[N] -> index into advisories
    # per-scheme sorted boundary keys (S-dtype byte strings)
    boundaries: dict[str, np.ndarray]
    # flat advisory list: (bucket, pkg_name, Advisory)
    advisories: list[tuple[str, str, Advisory]]
    # names too hot for the window: (space, name) -> list[adv_idx].
    # Their rows live in the hot partition below; this map is the
    # routing key (and the pure-host fallback when no device is used).
    host_fallback: dict[tuple[str, str], list[int]]
    window: int
    # hot partition: rows of names whose group exceeds `window`, laid
    # out identically but matched with their own (larger) window so
    # "linux"-class names stay on device instead of degenerating to a
    # per-advisory host loop
    hot_h1: np.ndarray | None = None
    hot_h2: np.ndarray | None = None
    hot_lo: np.ndarray | None = None
    hot_hi: np.ndarray | None = None
    hot_flags: np.ndarray | None = None
    hot_adv: np.ndarray | None = None
    hot_window: int = 0
    # tall tier: the few truly giant name groups ("linux"-class, group
    # above the adaptive mid/tall split — between HOT_MID_WINDOW and 4x
    # it, see compile_db). Splitting them out keeps the mid tier's
    # window — and with it the per-query result transfer (B x window
    # bits) and gather volume — ~6x smaller; only queries for a tall
    # name pay the tall window. The result link may be a ~5 MB/s tunnel,
    # so result bytes are the scarce resource.
    tall_h1: np.ndarray | None = None
    tall_h2: np.ndarray | None = None
    tall_lo: np.ndarray | None = None
    tall_hi: np.ndarray | None = None
    tall_flags: np.ndarray | None = None
    tall_adv: np.ndarray | None = None
    tall_window: int = 0
    # (space, name) routing set for the tall tier (subset of
    # host_fallback's keys)
    tall_names: set = field(default_factory=set)
    stats: dict = field(default_factory=dict)
    # token dicts injected by the match engine (see PackageBatch.ntok).
    # version_tokens doubles as the version intern map: its values ARE
    # the dense ids indexing _ver_rank/_ver_flags below. Inject BEFORE
    # the first encode (MatchEngine.__init__ does) — later injection
    # would leave already-interned entries without engine tokens.
    name_tokens: dict | None = field(default=None, repr=False)
    version_tokens: dict | None = field(default=None, repr=False)
    # intern tables (lazy): (space, name) -> dense name id with parallel
    # h1/h2/token/route columns; (scheme, version) -> dense version id
    # with parallel rank/flags columns. Batch encode then collapses to
    # one dict get per DISTINCT component plus numpy gathers — the
    # per-query hashing/keying/ranking of the old encode loop runs only
    # for first-seen names/versions.
    _names: dict = field(default_factory=dict, repr=False)
    _name_h1: "_Grow | None" = field(default=None, repr=False)
    _name_h2: "_Grow | None" = field(default=None, repr=False)
    _name_tok: "_Grow | None" = field(default=None, repr=False)
    _name_route: "_Grow | None" = field(default=None, repr=False)
    _vers: dict = field(default_factory=dict, repr=False)
    _ver_rank: "_Grow | None" = field(default=None, repr=False)
    _ver_flags: "_Grow | None" = field(default=None, repr=False)
    # guards intern-table mutation: the RPC server runs CONCURRENT
    # scans on one shared engine (read-locked, not exclusive), so two
    # first-seen components must not race the dense-id assignment
    _intern_lock: object = field(
        default_factory=lambda: make_lock("tensorize.compile._intern_lock"),
        repr=False)

    @property
    def n_rows(self) -> int:
        return len(self.row_h1)

    def rank_of_key(self, scheme_name: str, key: bytes) -> int:
        """Scaled rank of an encoded version key within a scheme's boundary
        table (see module docstring)."""
        return _rank_of(self.boundaries.get(scheme_name), key)

    def reset_name_intern(self) -> None:
        """Drop the (space, name) intern table (memo-bound shedding;
        names re-intern on demand with identical results). Version ids
        are untouched — they are embedded in the engine's rescreen memo
        keys and may only reset together with that memo."""
        self._names = {}
        self._name_h1 = self._name_h2 = None
        self._name_tok = self._name_route = None

    def reset_intern(self) -> None:
        """Drop BOTH intern tables. version_tokens may be the engine's
        `_version_tokens` dict — the caller that clears it (together
        with its rescreen memo, whose keys embed the version ids) must
        call this so the parallel rank/flags columns reset with it."""
        self.reset_name_intern()
        self._vers = {} if self.version_tokens is None \
            else self.version_tokens
        self._ver_rank = self._ver_flags = None

    def _ensure_intern(self) -> None:
        if self._name_h1 is None:
            self._name_h1 = _Grow(np.uint32)
            self._name_h2 = _Grow(np.uint32)
            self._name_tok = _Grow(np.int64)
            self._name_route = _Grow(np.int8)
            self._names = {}
        if self._ver_rank is None:
            self._ver_rank = _Grow(np.int32)
            self._ver_flags = _Grow(np.int32)
            # the engine's version-token dict IS the intern map when
            # injected, so collect-side memo keys and intern ids agree
            self._vers = self.version_tokens \
                if self.version_tokens is not None else {}
            self._vers.clear()

    def _intern_name(self, key: tuple[str, str]) -> int:
        j = len(self._names)
        self._names[key] = j
        h1, h2 = join_key(*key)
        self._name_h1.append(h1)
        self._name_h2.append(h2)
        self._name_tok.append(
            self.name_tokens.get(key, -2)
            if self.name_tokens is not None else -2)
        route = 0
        if key in self.host_fallback:
            route = 2 if key in self.tall_names else 1
        self._name_route.append(route)
        return j

    def _intern_version(self, ck: tuple[str, str],
                        staged: dict | None = None) -> int:
        scheme_name, version = ck
        t = len(self._vers)
        self._vers[ck] = t
        key, exact = versioning.get_scheme(scheme_name).key(version)
        fl = 0
        if not exact:
            fl = FLAG_NEEDS_HOST
        elif scheme_name == "npm" and "-" in version:
            # npm pre-release rule: interval hits are a superset for
            # pre-release versions -> exact host rescreen
            fl = FLAG_RESCREEN
        self._ver_flags.append(fl)
        if staged is None:
            self._ver_rank.append(
                _rank_of(self.boundaries.get(scheme_name), key))
        else:
            # cold-batch path: rank placeholder now, ONE vectorized
            # searchsorted per scheme once the whole batch is interned
            self._ver_rank.append(0)
            ids, keys = staged.setdefault(scheme_name, ([], []))
            ids.append(t)
            keys.append(key)
        return t

    def _flush_staged_ranks(self, staged: dict) -> None:
        ranks = self._ver_rank.view()
        for scheme_name, (ids, keys) in staged.items():
            bounds = self.boundaries.get(scheme_name)
            if bounds is None or len(bounds) == 0:
                continue
            ranks[np.asarray(ids, dtype=np.int64)] = _ranks_of(bounds, keys)

    def encode_packages(self, queries: list) -> PackageBatch:
        """queries: [(space, name, version, scheme_name)] -> PackageBatch.

        Hot path: names and versions intern to dense ids with parallel
        numpy columns (hash, engine token, tier route; scaled rank,
        flags), so a batch encodes as one dict get per component plus
        pure array gathers — hashing, version keying and the rank
        searchsorted run only for first-seen names/versions, not per
        query per batch."""
        n = len(queries)
        nid = np.empty(n, dtype=np.int64)
        vid = np.empty(n, dtype=np.int64)
        staged: dict = {}
        # the whole intern pass runs under the lock: concurrent server
        # scans on one shared engine must not race dense-id assignment,
        # and a staged (not-yet-ranked) version must not be observable
        # by another encode before _flush_staged_ranks finalizes it
        with self._intern_lock:
            self._ensure_intern()
            names = self._names
            vers = self._vers
            for i, q in enumerate(queries):
                space, name, version, scheme_name = q
                j = names.get((space, name))
                if j is None:
                    j = self._intern_name((space, name))
                nid[i] = j
                t = vers.get((scheme_name, version))
                if t is None:
                    t = self._intern_version((scheme_name, version),
                                             staged)
                vid[i] = t
            if staged:
                self._flush_staged_ranks(staged)
        return PackageBatch(
            h1=self._name_h1.view()[nid],
            h2=self._name_h2.view()[nid],
            rank=self._ver_rank.view()[vid],
            flags=self._ver_flags.view()[vid],
            queries=queries,
            ntok=(self._name_tok.view()[nid]
                  if self.name_tokens is not None else None),
            vtok=(vid if self.version_tokens is not None else None),
            route=self._name_route.view()[nid],
        )


def _advisory_intervals(
    adv: Advisory, scheme_name: str, eco: str | None
) -> list[tuple] | None:
    """-> [(lo_str|None, lo_incl, hi_str|None, hi_incl, flags)] or None for
    needs-host (unparseable / always-candidate).

    npm with secure ranges emits TWO row sets: the subtracted intervals
    (exact for non-pre-release query versions — the npm pre-release rule
    only ever *removes* matches, and removes none for a non-pre-release
    version), plus the unsubtracted vulnerable intervals gated with
    FLAG_PRE_ONLY | FLAG_RESCREEN. A pre-release query (which the encoder
    flags FLAG_RESCREEN) may be truly vulnerable at a point the order-level
    subtraction removed — a secure range can cover the point on the total
    order without "covering" the pre-release per the npm rule — so those
    queries match against the unsubtracted superset and every such hit is
    host-rescreened with the exact comparators."""
    scheme = versioning.get_scheme(scheme_name)
    if adv.is_range_style:
        # empty string in vulnerable/patched => always vulnerable
        # (reference compare.go:23-27)
        for v in list(adv.vulnerable_versions) + list(adv.patched_versions):
            if v == "":
                return [(None, True, None, True, 0)]
        npm_mode = scheme.name == "npm"
        try:
            if adv.vulnerable_versions:
                vuln = Constraints(
                    scheme, " || ".join(adv.vulnerable_versions), npm_mode
                ).intervals()
            else:
                vuln = [versioning.Interval()]
            secure_exprs = list(adv.patched_versions) + list(adv.unaffected_versions)
            pre_rows: list = []
            if secure_exprs:
                if npm_mode:
                    pre_rows = [
                        (_vs(iv.lo), iv.lo_incl, _vs(iv.hi), iv.hi_incl,
                         FLAG_PRE_ONLY | FLAG_RESCREEN)
                        for iv in vuln
                    ]
                secure = Constraints(
                    scheme, " || ".join(secure_exprs), npm_mode
                ).intervals()
                vuln = _subtract(vuln, secure, scheme)
        except ParseError:
            return None
        return [
            (_vs(iv.lo), iv.lo_incl, _vs(iv.hi), iv.hi_incl, 0)
            for iv in vuln
        ] + pre_rows
    # OS style: [affected, fixed) — no fixed version => unbounded above
    lo = adv.affected_version or None
    hi = adv.fixed_version or None
    return [(lo, True, hi, False, 0)]


def _vs(parsed) -> str | None:
    if parsed is None:
        return None
    raw = getattr(parsed, "raw", None)
    return raw if raw is not None else str(parsed)


def _subtract(vuln: list, secure: list, scheme) -> list:
    """Union-of-intervals subtraction: vuln minus secure. The surviving
    pieces are v ∩ (-inf, s.lo) and v ∩ (s.hi, +inf) for each secure s."""
    from trivy_tpu.versioning.constraints import Interval, _intersect

    out = list(vuln)
    for s in secure:
        nxt = []
        for v in out:
            if s.lo is not None:
                below = _intersect(
                    v, Interval(None, True, s.lo, not s.lo_incl), scheme
                )
                if below is not None:
                    nxt.append(below)
            if s.hi is not None:
                above = _intersect(
                    v, Interval(s.hi, not s.hi_incl, None, True), scheme
                )
                if above is not None:
                    nxt.append(above)
        out = nxt
        if not out:
            break
    return out


MAX_AUTO_WINDOW = 512
# hot-tier split point: name groups above this go to the "tall"
# partition so mid-tier queries don't pay giant-group windows
HOT_MID_WINDOW = 256


def flat_advisories(db: AdvisoryDB) -> list[tuple[str, str, Advisory]]:
    """The flat (bucket, name, Advisory) list every CompiledDB indexes
    into, in the DEFINED iteration order (bucket insertion order, names
    in insertion order, non-matchable buckets skipped).

    This order is the contract between `compile_db` and the persistent
    compiled-DB cache: a cached tensor set stores advisory *indices*, so
    the loader rebuilds this list from the (re-)loaded DB and the
    indices line up by construction."""
    out: list[tuple[str, str, Advisory]] = []
    for bucket, pkgs in db.buckets.items():
        if space_of_bucket(bucket) is None:
            _log.debug("bucket not matchable, skipping", bucket=bucket)
            continue
        for name, advs in pkgs.items():
            for adv in advs:
                out.append((bucket, name, adv))
    return out


def compile_db(db: AdvisoryDB, window: int | None = None) -> CompiledDB:
    """window=None: size the gather window to the largest per-hash row
    group (rounded up to a multiple of 8, capped at MAX_AUTO_WINDOW —
    result-transfer volume is B x window, so a tight window matters on
    tunneled devices)."""
    advisories: list[tuple[str, str, Advisory]] = []
    raw_rows: list[dict] = []
    boundary_keys: dict[str, set] = {}
    n_host_rows = 0

    # version-string -> (key, exact) memo: fixed versions repeat heavily
    # in real trivy-db (the same "2.4.1-r0" appears across many CVEs)
    key_memo: dict[tuple[str, str], tuple[bytes, bool]] = {}

    for bucket, pkgs in db.buckets.items():
        resolved = space_of_bucket(bucket)
        if resolved is None:
            _log.debug("bucket not matchable, skipping", bucket=bucket)
            continue
        space, scheme_name = resolved
        scheme = versioning.get_scheme(scheme_name)
        eco = bucket.split("::", 1)[0] if "::" in bucket else None
        for name, advs in pkgs.items():
            h1, h2 = join_key(space, name)
            for adv in advs:
                adv_idx = len(advisories)
                advisories.append((bucket, name, adv))
                compiled = _advisory_intervals(adv, scheme_name, eco)
                if compiled is None:
                    raw_rows.append(dict(
                        h1=h1, h2=h2, space=space, name=name,
                        lo_key=None, hi_key=None, lo_incl=True, hi_incl=True,
                        scheme=scheme_name, flags=FLAG_NEEDS_HOST, adv=adv_idx,
                    ))
                    n_host_rows += 1
                    continue
                for lo_str, lo_incl, hi_str, hi_incl, iv_flags in compiled:
                    flags = iv_flags
                    lo_key = hi_key = None
                    if lo_str is not None:
                        mk = key_memo.get((scheme_name, lo_str))
                        if mk is None:
                            mk = scheme.key(lo_str)
                            key_memo[(scheme_name, lo_str)] = mk
                        lo_key, exact = mk
                        if not exact:
                            flags |= FLAG_NEEDS_HOST
                    if hi_str is not None:
                        mk = key_memo.get((scheme_name, hi_str))
                        if mk is None:
                            mk = scheme.key(hi_str)
                            key_memo[(scheme_name, hi_str)] = mk
                        hi_key, exact = mk
                        if not exact:
                            flags |= FLAG_NEEDS_HOST
                    if flags & FLAG_NEEDS_HOST:
                        n_host_rows += 1
                        lo_key = hi_key = None
                    else:
                        ks = boundary_keys.setdefault(scheme_name, set())
                        if lo_key is not None:
                            ks.add(lo_key)
                        if hi_key is not None:
                            ks.add(hi_key)
                    raw_rows.append(dict(
                        h1=h1, h2=h2, space=space, name=name,
                        lo_key=lo_key, hi_key=hi_key,
                        lo_incl=lo_incl, hi_incl=hi_incl,
                        scheme=scheme_name, flags=flags, adv=adv_idx,
                    ))

    # boundary tables
    boundaries = {
        s: np.sort(np.array(sorted(keys), dtype=f"S{KEY_BYTES}"))
        for s, keys in boundary_keys.items()
    }

    # partition: names with too many rows for the window go to a hot
    # partition with its own window (matched on device too; see
    # CompiledDB.hot_*)
    from collections import Counter, defaultdict

    # count per h1 alone: the kernel's window starts at the first h1 match,
    # so h1-colliding names share one window and must be evicted together
    counts = Counter(r["h1"] for r in raw_rows)
    auto_window = window is None
    if auto_window:
        max_count = max(counts.values(), default=1)
        window = min(max(8, -(-max_count // 8) * 8), MAX_AUTO_WINDOW)
    host_fallback: dict[tuple[str, str], list[int]] = defaultdict(list)
    kept: list[dict] = []
    hot: list[dict] = []
    for r in raw_rows:
        if counts[r["h1"]] > window:
            host_fallback[(r["space"], r["name"])].append(r["adv"])
            hot.append(r)
            continue
        kept.append(r)
    if auto_window and hot:
        # eviction guarantees every kept group fits a (possibly much)
        # smaller window than the pre-eviction bound; shrink it — result
        # transfer is B x window, so this is pure savings
        max_kept = max((counts[r["h1"]] for r in kept), default=1)
        window = max(8, -(-max_kept // 8) * 8)
    # dedupe fallback advisory ids (multi-interval advisories)
    host_fallback = {
        k: sorted(set(v)) for k, v in host_fallback.items()
    }

    def fill(rows: list[dict]):
        """rows -> (h1, h2, lo, hi, flags, adv) arrays, (h1,h2)-sorted.
        Rank assignment is batched: ONE searchsorted per (scheme, side)
        instead of one per row — the difference between seconds and
        minutes at real trivy-db scale (millions of rows)."""
        rows.sort(key=lambda r: (r["h1"], r["h2"]))
        n = len(rows)
        a_h1 = np.zeros(n, dtype=np.uint32)
        a_h2 = np.zeros(n, dtype=np.uint32)
        a_lo = np.zeros(n, dtype=np.int32)
        a_hi = np.full(n, INT32_MAX, dtype=np.int32)
        a_flags = np.zeros(n, dtype=np.int32)
        a_adv = np.zeros(n, dtype=np.int32)
        pending: dict[str, tuple[list, list, list, list]] = {}
        for i, r in enumerate(rows):
            a_h1[i], a_h2[i] = r["h1"], r["h2"]
            a_flags[i], a_adv[i] = r["flags"], r["adv"]
            if r["flags"] & FLAG_NEEDS_HOST:
                a_lo[i], a_hi[i] = 0, INT32_MAX
                continue
            idxs, keys, sides, incls = pending.setdefault(
                r["scheme"], ([], [], [], []))
            if r["lo_key"] is not None:
                idxs.append(i); keys.append(r["lo_key"])
                sides.append(0); incls.append(r["lo_incl"])
            if r["hi_key"] is not None:
                idxs.append(i); keys.append(r["hi_key"])
                sides.append(1); incls.append(r["hi_incl"])
        for scheme_name, (idxs, keys, sides, incls) in pending.items():
            bounds = boundaries.get(scheme_name)
            if bounds is None or len(bounds) == 0 or not idxs:
                continue
            rank = _ranks_of(bounds, keys)
            ii = np.array(idxs)
            ss = np.array(sides)
            inc = np.array(incls)
            lo_sel = ss == 0
            a_lo[ii[lo_sel]] = rank[lo_sel] + (~inc[lo_sel])
            hi_sel = ~lo_sel
            a_hi[ii[hi_sel]] = rank[hi_sel] - (~inc[hi_sel])
        return a_h1, a_h2, a_lo, a_hi, a_flags, a_adv

    row_h1, row_h2, row_lo, row_hi, row_flags, row_adv = fill(kept)
    # tier the hot rows: mid groups vs the giant "tall" groups, so a
    # mid-name query never pays the tallest group's window in gather
    # volume or result bytes. The split adapts to the distribution —
    # the (lower) median hot-group size, floored at HOT_MID_WINDOW —
    # so roughly half the hot groups pay <= median instead of max;
    # capped at 4x HOT_MID_WINDOW so one giant group at the median can
    # never drag small hot groups onto a huge window
    group_sizes = sorted(counts[h1] for h1 in {r["h1"] for r in hot})
    split = HOT_MID_WINDOW
    if group_sizes:
        split = min(max(HOT_MID_WINDOW,
                        group_sizes[(len(group_sizes) - 1) // 2]),
                    4 * HOT_MID_WINDOW)
    mid: list[dict] = []
    tall: list[dict] = []
    tall_names: set = set()
    for r in hot:
        if counts[r["h1"]] > split:
            tall.append(r)
            tall_names.add((r["space"], r["name"]))
        else:
            mid.append(r)
    hot_arrays = fill(mid) if mid else None
    hot_window = 0
    if mid:
        hot_max = max(Counter(r["h1"] for r in mid).values())
        hot_window = -(-hot_max // 8) * 8
    tall_arrays = fill(tall) if tall else None
    tall_window = 0
    if tall:
        tall_max = max(Counter(r["h1"] for r in tall).values())
        tall_window = -(-tall_max // 8) * 8

    stats = {
        "rows": len(kept),
        "advisories": len(advisories),
        "host_rows": n_host_rows,
        "fallback_names": len(host_fallback),
        "hot_rows": len(mid),
        "hot_window": hot_window,
        "tall_rows": len(tall),
        "tall_window": tall_window,
        "boundary_keys": {s: len(b) for s, b in boundaries.items()},
    }
    _log.info("compiled advisory DB", **stats)
    return CompiledDB(
        row_h1=row_h1, row_h2=row_h2, row_lo=row_lo, row_hi=row_hi,
        row_flags=row_flags, row_adv=row_adv,
        boundaries=boundaries, advisories=advisories,
        host_fallback=dict(host_fallback), window=window,
        hot_h1=hot_arrays[0] if hot_arrays else None,
        hot_h2=hot_arrays[1] if hot_arrays else None,
        hot_lo=hot_arrays[2] if hot_arrays else None,
        hot_hi=hot_arrays[3] if hot_arrays else None,
        hot_flags=hot_arrays[4] if hot_arrays else None,
        hot_adv=hot_arrays[5] if hot_arrays else None,
        hot_window=hot_window,
        tall_h1=tall_arrays[0] if tall_arrays else None,
        tall_h2=tall_arrays[1] if tall_arrays else None,
        tall_lo=tall_arrays[2] if tall_arrays else None,
        tall_hi=tall_arrays[3] if tall_arrays else None,
        tall_flags=tall_arrays[4] if tall_arrays else None,
        tall_adv=tall_arrays[5] if tall_arrays else None,
        tall_window=tall_window, tall_names=tall_names,
        stats=stats,
    )
