from trivy_tpu.tensorize.compile import CompiledDB, PackageBatch, compile_db

__all__ = ["CompiledDB", "PackageBatch", "compile_db"]
